"""Crash-consistent boot: the clean-shutdown marker, torn-store
recovery, prior-incident discovery, and the db reconciliation sweep.

The reference earns its `kill -9`-at-any-instant survival from three
disciplines — gossip_store truncate-on-corruption, sqlite WAL, and
startup passes that resolve every in-flight row against what actually
became durable.  This module is that boot phase (doc/recovery.md):

1. read the marker: was the previous run shut down cleanly?
2. on a crash boot, discover the incident bundles the black box
   (obs/incident.py) froze for the dead run and log/meter them —
   forensics travel WITH the restart, not behind it;
3. recover the gossip store (gossip/store.py recover_store): torn tail
   truncated write-then-rename, crc-bad rows quarantined + host
   re-checked, missing store bootstrapped;
4. optionally replay the recovered store through the batched verify
   pipeline (LIGHTNING_TPU_RECOVERY_VERIFY — recovery is the one
   guaranteed-full-occupancy workload);
5. sweep the db: pending payments older than the crash become
   retryable-failed (no pending-forever phantoms in listpays),
   retransmission-journal and splice-inflight blobs are validated
   against channel state, and a hook replica that is "ahead by one"
   (wallet/db.py's documented crash window) drops its tail record.

tools/crashmatrix.py kills a live daemon at every armed seam and
asserts this module brings it back to the durable-prefix oracle.
"""
from __future__ import annotations

import json
import logging
import os
import re
import time

from ..obs import families as _f
from ..utils import events

log = logging.getLogger("lightning_tpu.daemon.recovery")

MARKER_NAME = "run_marker"
# channel states with no live peer protocol: journal blobs there are
# stale by definition (wallet.py restore skips these states too)
DEAD_STATES = ("closingd_complete", "onchain", "closed")
_INC_RE = re.compile(r"^inc-[0-9]+-[0-9]+$")


# -- clean-shutdown marker --------------------------------------------------
# <data-dir>/run_marker: "running" while the daemon is up, "clean" after
# an orderly shutdown.  Written write-then-rename + fsync, so the marker
# itself can never be read torn; a missing marker means first boot.

def marker_path(data_dir: str) -> str:
    return os.path.join(data_dir, MARKER_NAME)


def _write_marker(data_dir: str, state: str) -> None:
    path = marker_path(data_dir)
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf8") as f:
        f.write(state + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def mark_running(data_dir: str) -> None:
    _write_marker(data_dir, "running")


def mark_clean(data_dir: str) -> None:
    _write_marker(data_dir, "clean")


def read_marker(data_dir: str) -> str:
    """"first_boot" (no marker), "clean", or "crash" (marker still says
    running — or says anything unrecognizable, which only a crash
    mid-everything could leave)."""
    try:
        with open(marker_path(data_dir), encoding="utf8") as f:
            content = f.read().strip()
    except OSError:
        return "first_boot"
    return "clean" if content == "clean" else "crash"


# -- prior-incident discovery ----------------------------------------------

def discover_incidents(data_dir: str) -> list[dict]:
    """Bundle summaries from the previous run's incident directory
    (newest last).  Reads the on-disk manifests directly — the new
    recorder instance hasn't started yet at this point in boot."""
    inc_dir = os.environ.get("LIGHTNING_TPU_INCIDENT_DIR") or os.path.join(
        data_dir, "incidents")
    try:
        names = sorted(
            (n for n in os.listdir(inc_dir) if _INC_RE.match(n)),
            key=lambda n: (int(n.split("-")[1]), int(n.split("-")[2])))
    except OSError:
        return []
    out = []
    for name in names:
        row = {"id": name, "trigger": None, "captured_at": None}
        try:
            with open(os.path.join(inc_dir, name, "manifest.json"),
                      encoding="utf8") as f:
                man = json.load(f)
            row["trigger"] = (man.get("trigger") or {}).get("class")
            row["captured_at"] = man.get("captured_at")
        except (OSError, ValueError):
            row["trigger"] = "unreadable"
        out.append(row)
    return out


# -- crc-bad host re-check --------------------------------------------------

def host_sig_checker():
    """Returns check_sigs(msgs) -> [bool] for recover_store(): parse +
    verify every signature with the pure-python oracle (crypto/
    ref_python — no jax, no kernels).  A channel_update's key lives in
    its owning channel_announcement, so the checker closes over a
    lazily-built scid→keys map from the messages themselves; a CU whose
    CA is not in the batch cannot be requalified (fails closed)."""
    from ..crypto import ref_python as ref
    from ..gossip import wire

    def _verify_one(sig: bytes, pubkey: bytes, region: bytes) -> bool:
        try:
            r = int.from_bytes(sig[:32], "big")
            s = int.from_bytes(sig[32:], "big")
            return ref.ecdsa_verify(ref.sha256d(region), r, s,
                                    ref.pubkey_parse(pubkey))
        except Exception:
            return False

    def check_sigs(msgs) -> list[bool]:
        parsed = []
        scid_keys: dict[int, tuple[bytes, bytes]] = {}
        for m in msgs:
            try:
                p = wire.parse_gossip(bytes(m))
            except Exception:
                p = None
            parsed.append(p)
            if isinstance(p, wire.ChannelAnnouncement):
                scid_keys[p.short_channel_id] = (p.node_id_1, p.node_id_2)
        out = []
        for m, p in zip(msgs, parsed):
            m = bytes(m)
            if isinstance(p, wire.ChannelAnnouncement):
                region = m[wire.CA_SIGNED_OFFSET:]
                out.append(all(
                    _verify_one(sig, key, region)
                    for sig, key in p.signature_tuples()))
            elif isinstance(p, wire.NodeAnnouncement):
                out.append(_verify_one(
                    p.signature, p.node_id, m[wire.NA_SIGNED_OFFSET:]))
            elif isinstance(p, wire.ChannelUpdate):
                keys = scid_keys.get(p.short_channel_id)
                out.append(keys is not None and _verify_one(
                    p.signature, keys[p.direction],
                    m[wire.CU_SIGNED_OFFSET:]))
            else:
                out.append(False)
        return out

    return check_sigs


# -- db reconciliation sweep ------------------------------------------------

def _retransmit_valid(raw: bytes) -> bool:
    """Structural validity of a retransmission-journal blob (the
    _pack_retransmit format: 1 sealed byte + [u32-be len][msg]...).
    wallet._unpack_retransmit is deliberately tolerant; this walk is
    not — a crash-corrupted blob must be detected, not reinterpreted."""
    if not raw:
        return True
    if raw[0] not in (0, 1):
        return False
    off = 1
    while off < len(raw):
        if off + 4 > len(raw):
            return False
        ln = int.from_bytes(raw[off : off + 4], "big")
        if off + 4 + ln > len(raw):
            return False
        off += 4 + ln
    return True


def reconcile_db(db, *, now: float | None = None) -> dict:
    """The boot sweep over wallet state (one transaction):

    * payments still 'pending' predate this boot by construction (the
      sweep runs before any RPC is served) — each becomes
      status='failed' with a retryable failure note, so listpays never
      shows a pending-forever phantom;
    * channels.retransmit blobs that fail the structural walk, or that
      belong to dead-state channels, reset to empty (a reestablish
      will renegotiate; replaying corrupt bytes would desync the peer);
    * channels.inflight splice blobs that are not valid JSON reset the
      same way.

    Returns {"payments_failed": n, "retransmit_reset": n,
    "inflight_reset": n}."""
    ts = int(now if now is not None else time.time())
    fixups = {"payments_failed": 0, "retransmit_reset": 0,
              "inflight_reset": 0}
    with db.transaction() as c:
        cur = c.execute(
            "UPDATE payments SET status='failed', completed_at=?, "
            "failure=? WHERE status='pending'",
            (ts, "daemon restarted before completion (crash recovery; "
                 "safe to retry)"))
        fixups["payments_failed"] = max(0, cur.rowcount)
        for cid, state, retransmit, inflight in c.execute(
                "SELECT id, state, retransmit, inflight "
                "FROM channels").fetchall():
            retransmit = retransmit or b""
            inflight = inflight or b""
            if retransmit and (state in DEAD_STATES
                               or not _retransmit_valid(retransmit)):
                c.execute("UPDATE channels SET retransmit=x'' WHERE id=?",
                          (cid,))
                fixups["retransmit_reset"] += 1
                log.warning("channel %d: retransmission journal reset "
                            "(state %s, %d bytes)", cid, state,
                            len(retransmit))
            if inflight:
                bad = state in DEAD_STATES
                if not bad:
                    try:
                        json.loads(inflight)
                    except ValueError:
                        bad = True
                if bad:
                    c.execute(
                        "UPDATE channels SET inflight=x'' WHERE id=?",
                        (cid,))
                    fixups["inflight_reset"] += 1
                    log.warning("channel %d: splice-inflight blob reset "
                                "(state %s)", cid, state)
    if fixups["payments_failed"]:
        _f.RECOVERY_DB_FIXUPS.labels("payment_failed").inc(
            fixups["payments_failed"])
    if fixups["retransmit_reset"]:
        _f.RECOVERY_DB_FIXUPS.labels("retransmit_reset").inc(
            fixups["retransmit_reset"])
    if fixups["inflight_reset"]:
        _f.RECOVERY_DB_FIXUPS.labels("inflight_reset").inc(
            fixups["inflight_reset"])
    return fixups


# -- the boot phase ---------------------------------------------------------

def boot_recover(data_dir: str, *, store_path: str | None = None,
                 db=None, replica=None, verify: bool | None = None,
                 now: float | None = None) -> dict:
    """Run the whole recovery phase and leave the marker at "running".

    Called from daemon/__main__.py after the wallet opens and BEFORE
    the gossmap/gossipd are built from the store (they must see the
    recovered file).  Returns a report dict; the "state" key is the
    marker verdict ("first_boot" | "clean" | "crash").

    LIGHTNING_TPU_RECOVERY_DISABLE=1 skips everything except the marker
    write; LIGHTNING_TPU_RECOVERY_VERIFY=0 skips the store verify
    replay on crash boots (`verify=` overrides the knob)."""
    t0 = time.perf_counter()
    state = read_marker(data_dir)
    report: dict = {"state": state, "incidents": [], "store": None,
                    "db_fixups": None, "replica": None,
                    "verify": None, "skipped": False}
    if state == "crash":
        _f.RECOVERY_BOOTS.labels("crash").inc()
    elif state == "clean":
        _f.RECOVERY_BOOTS.labels("clean").inc()
    else:
        _f.RECOVERY_BOOTS.labels("first_boot").inc()

    if os.environ.get("LIGHTNING_TPU_RECOVERY_DISABLE") == "1":
        report["skipped"] = True
        mark_running(data_dir)
        return report

    crashed = state == "crash"
    if crashed:
        log.warning("unclean shutdown detected (marker still said "
                    "running): entering crash recovery")
        incidents = discover_incidents(data_dir)
        report["incidents"] = incidents
        if incidents:
            _f.RECOVERY_INCIDENTS_FOUND.inc(len(incidents))
            newest = incidents[-1]
            log.warning("previous run left %d incident bundle(s); "
                        "newest: %s (trigger %s) — see listincidents",
                        len(incidents), newest["id"], newest["trigger"])

    if store_path is not None:
        from ..gossip import store as gstore

        # crc enforcement + host re-check only on crash boots: a clean
        # shutdown fsynced everything it appended, and the native scan
        # (always run, via load_store inside) still catches torn files
        check_sigs = host_sig_checker() if crashed else None
        idx, srep = gstore.recover_store(
            store_path, check_crc=crashed, check_sigs=check_sigs)
        report["store"] = {
            "bootstrapped": srep.bootstrapped, "records": srep.records,
            "size": srep.size, "truncated_bytes": srep.truncated_bytes,
            "crc_bad": srep.crc_bad, "requalified": srep.requalified,
            "dropped": srep.dropped,
        }
        report["_store_idx"] = idx
        if crashed:
            do_verify = (verify if verify is not None else
                         os.environ.get("LIGHTNING_TPU_RECOVERY_VERIFY",
                                        "1") != "0")
            if do_verify and srep.records:
                # replay the durable store through the batched verify
                # pipeline — full-occupancy by construction (every
                # alive record, one enqueue stream)
                from ..gossip import verify as gverify

                res = gverify.verify_store(idx)
                n_bad = (int((~res.ca_valid).sum())
                         + int((~res.cu_valid).sum())
                         + int((~res.na_valid).sum()))
                report["verify"] = {"records": res.n_records,
                                    "sigs": res.n_sigs,
                                    "invalid": n_bad}
                if n_bad:
                    log.warning("recovery verify replay: %d record(s) "
                                "failed signature re-verification",
                                n_bad)

    if db is not None and crashed:
        report["db_fixups"] = reconcile_db(db, now=now)
    if db is not None and replica is not None:
        from ..wallet.db import reconcile_file_replica

        verdict = reconcile_file_replica(db, replica)
        report["replica"] = verdict
        if verdict == "dropped_ahead":
            _f.RECOVERY_DB_FIXUPS.labels("replica_dropped").inc()

    mark_running(data_dir)
    dt = time.perf_counter() - t0
    _f.RECOVERY_SECONDS.observe(dt)
    events.emit("recovery_complete", {
        "state": state, "seconds": round(dt, 3),
        "incidents": len(report["incidents"]),
        "store": {k: v for k, v in (report["store"] or {}).items()},
        "db_fixups": report["db_fixups"], "replica": report["replica"]})
    if crashed:
        s = report["store"] or {}
        log.warning(
            "crash recovery complete in %.2fs: store %d records "
            "(%d torn bytes truncated, %d crc-bad: %d requalified / "
            "%d dropped), db fixups %s, replica %s",
            dt, s.get("records", 0), s.get("truncated_bytes", 0),
            s.get("crc_bad", 0), s.get("requalified", 0),
            s.get("dropped", 0), report["db_fixups"], report["replica"])
    return report
