"""reckless — the plugin package manager CLI.

Parity target: /root/reference/tools/reckless (install/uninstall/
enable/disable/list against a lightning-dir).  Sources are local
directories or git URLs (git clone; the reference also searches github
indexes, which needs egress).  Installed plugins live under
<lightning-dir>/reckless/<name>/ and enabled ones are listed in
<lightning-dir>/reckless/reckless.conf as `plugin=<path>` lines, which
the daemon loads at startup (daemon/__main__.py).

Usage:
  python -m lightning_tpu.reckless -l DIR install <path-or-git-url>
  python -m lightning_tpu.reckless -l DIR enable|disable <name>
  python -m lightning_tpu.reckless -l DIR uninstall <name>
  python -m lightning_tpu.reckless -l DIR list
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import stat
import subprocess
import sys


class RecklessError(Exception):
    pass


def _root(lightning_dir: str) -> str:
    p = os.path.join(lightning_dir, "reckless")
    os.makedirs(p, exist_ok=True)
    return p


def _conf_path(lightning_dir: str) -> str:
    return os.path.join(_root(lightning_dir), "reckless.conf")


def _read_conf(lightning_dir: str) -> list[str]:
    try:
        with open(_conf_path(lightning_dir)) as f:
            return [line.strip() for line in f if line.strip()]
    except FileNotFoundError:
        return []


def _write_conf(lightning_dir: str, lines: list[str]) -> None:
    with open(_conf_path(lightning_dir), "w") as f:
        f.write("\n".join(lines) + ("\n" if lines else ""))


def _entrypoint(plugin_dir: str, name: str) -> str:
    """The executable the daemon will spawn: <name>.py, <name>, or a
    single executable file in the directory."""
    for cand in (f"{name}.py", name):
        p = os.path.join(plugin_dir, cand)
        if os.path.isfile(p):
            return p
    execs = [os.path.join(plugin_dir, f) for f in os.listdir(plugin_dir)
             if os.path.isfile(os.path.join(plugin_dir, f))
             and os.access(os.path.join(plugin_dir, f), os.X_OK)]
    if len(execs) == 1:
        return execs[0]
    pys = [os.path.join(plugin_dir, f) for f in os.listdir(plugin_dir)
           if f.endswith(".py") and not f.startswith("_")]
    if len(pys) == 1:
        return pys[0]
    raise RecklessError(
        f"cannot determine entrypoint for {name} "
        f"(no {name}.py/{name}, {len(execs)} executables, "
        f"{len(pys)} python files)")


def install(lightning_dir: str, source: str) -> dict:
    name = os.path.basename(source.rstrip("/")).removesuffix(".git")
    dest = os.path.join(_root(lightning_dir), name)
    if os.path.exists(dest):
        raise RecklessError(f"{name} already installed")
    if os.path.isdir(source):
        shutil.copytree(source, dest)
    else:
        r = subprocess.run(["git", "clone", "--depth", "1", source,
                            dest], capture_output=True, text=True)
        if r.returncode != 0:
            raise RecklessError(f"git clone failed: "
                                f"{r.stderr.strip()[:200]}")
    entry = _entrypoint(dest, name)
    os.chmod(entry, os.stat(entry).st_mode | stat.S_IXUSR)
    return {"name": name, "path": dest, "entrypoint": entry,
            "enabled": False}


def uninstall(lightning_dir: str, name: str) -> dict:
    disable(lightning_dir, name, missing_ok=True)
    dest = os.path.join(_root(lightning_dir), name)
    if not os.path.isdir(dest):
        raise RecklessError(f"{name} is not installed")
    shutil.rmtree(dest)
    return {"name": name, "uninstalled": True}


def enable(lightning_dir: str, name: str) -> dict:
    dest = os.path.join(_root(lightning_dir), name)
    if not os.path.isdir(dest):
        raise RecklessError(f"{name} is not installed")
    entry = _entrypoint(dest, name)
    lines = _read_conf(lightning_dir)
    want = f"plugin={entry}"
    if want not in lines:
        lines.append(want)
        _write_conf(lightning_dir, lines)
    return {"name": name, "entrypoint": entry, "enabled": True}


def disable(lightning_dir: str, name: str,
            missing_ok: bool = False) -> dict:
    dest = os.path.join(_root(lightning_dir), name)
    lines = _read_conf(lightning_dir)
    kept = [line for line in lines
            if not line.startswith("plugin=")
            or os.path.dirname(line.split("=", 1)[1]) != dest]
    if len(kept) == len(lines) and not missing_ok:
        raise RecklessError(f"{name} is not enabled")
    _write_conf(lightning_dir, kept)
    return {"name": name, "enabled": False}


def list_installed(lightning_dir: str) -> list[dict]:
    root = _root(lightning_dir)
    enabled_dirs = {
        os.path.dirname(line.split("=", 1)[1])
        for line in _read_conf(lightning_dir)
        if line.startswith("plugin=")}
    out = []
    for name in sorted(os.listdir(root)):
        p = os.path.join(root, name)
        if os.path.isdir(p):
            out.append({"name": name, "path": p,
                        "enabled": p in enabled_dirs})
    return out


def enabled_plugins(lightning_dir: str) -> list[str]:
    """Entrypoints the daemon should spawn (reckless.conf contents)."""
    return [line.split("=", 1)[1]
            for line in _read_conf(lightning_dir)
            if line.startswith("plugin=")]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="reckless")
    p.add_argument("-l", "--lightning-dir", required=True)
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("install").add_argument("source")
    sub.add_parser("uninstall").add_argument("name")
    sub.add_parser("enable").add_argument("name")
    sub.add_parser("disable").add_argument("name")
    sub.add_parser("list")
    args = p.parse_args(argv)
    try:
        if args.cmd == "install":
            out = install(args.lightning_dir, args.source)
        elif args.cmd == "uninstall":
            out = uninstall(args.lightning_dir, args.name)
        elif args.cmd == "enable":
            out = enable(args.lightning_dir, args.name)
        elif args.cmd == "disable":
            out = disable(args.lightning_dir, args.name)
        else:
            out = list_installed(args.lightning_dir)
    except RecklessError as e:
        print(f"reckless: {e}", file=sys.stderr)
        return 1
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
