"""Device-mesh and sharding helpers for the batched-crypto data plane.

CLN's "distributed backend" is a fleet of single-purpose processes wired by
socketpairs (SURVEY.md §2.5); the TPU-native equivalent moves the heavy
math (signature verify/sign fan-out) onto a device mesh and keeps the
protocol plane on host.  Scaling axis:

* ``batch``: data-parallel over signatures.  A verify batch of B sigs is
  sharded (B/n per device); each device runs the identical branchless
  kernel; the only collective is the boolean gather at the end (and a
  psum for the "all valid" fast path) — pure ICI traffic, no host hop.

This mirrors how the reference scales gossip verification across...
nothing (it is serial, gossipd/sigcheck.c) — the mesh IS the delta.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax < 0.6 ships shard_map under experimental only; the top-level alias
# this module was written against does not exist on the pinned 0.4.x.
# Public on purpose: __graft_entry__.py shares this compat shim.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map

BATCH_AXIS = "batch"


def make_mesh(devices=None) -> Mesh:
    """1-D data-parallel mesh over all (or the given) devices."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (BATCH_AXIS,))


def usable_device_count(batch: int, limit: int | None = None) -> int:
    """Largest device count ≤ limit (default: all devices) that divides
    the batch evenly — shard_map rejects ragged shards, so a bucket
    must split exactly.  Returns 1 when no multi-device split fits
    (callers fall back to the single-device program)."""
    try:
        n = len(jax.devices())
    except Exception:
        return 1
    if limit is not None:
        n = min(n, limit)
    while n > 1 and batch % n:
        n -= 1
    return max(1, n)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(BATCH_AXIS))


def pad_to_multiple(arr: np.ndarray, multiple: int, axis: int = 0):
    """Pad with trailing zeros so shape[axis] % multiple == 0.
    Returns (padded, original_length)."""
    n = arr.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return arr, n
    pad = [(0, 0)] * arr.ndim
    pad[axis] = (0, rem)
    return np.pad(arr, pad), n


def shard_batch(mesh: Mesh, *arrays):
    """device_put each array with leading-axis sharding over the mesh.
    Arrays must already be padded to a multiple of the mesh size."""
    # named fault seam (doc/resilience.md): the resharding device_put is
    # the first mesh-only step of a sharded dispatch, so an injected
    # failure here exercises the mesh breaker's mesh→fused degradation
    from ..resilience import faultinject as _fault
    from ..utils import trace

    _fault.fire("mesh", "mesh")
    sh = batch_sharding(mesh)
    # under LIGHTNING_TPU_PROFILE the reshard cost shows up as its own
    # host-lane slice next to the shard_map program (doc/tracing.md)
    with trace.annotation("mesh/reshard"):
        return tuple(jax.device_put(a, sh) for a in arrays)


@functools.lru_cache(maxsize=16)
def sharded_verify_fn(mesh: Mesh, compiler_options: tuple = ()):
    """jit-compiled ECDSA verify step sharded over the mesh's batch axis.

    Inputs: z, r, s, qx (B, NLIMBS) uint32 limb planes; parity (B,)
    uint32 — B divisible by the mesh size.  Output: bool (B,) with the
    same sharding, plus a replicated scalar count of valid sigs (forces
    a psum collective, which doubles as the aggregate "how many failed"
    signal gossipd wants).

    Production consumer: gossip/verify.py verify_items routes replay
    buckets here when the process has >1 device (the mesh path of the
    streaming pipeline, doc/replay_pipeline.md); __graft_entry__'s
    multichip dryrun exercises the same program on the virtual CPU
    mesh."""
    from ..crypto import secp256k1 as S

    def step(z, r, s, qx, parity):
        ok = S.ecdsa_verify_kernel(z, r, s, qx, parity)
        return ok, jax.lax.psum(jnp.sum(ok.astype(jnp.uint32)), BATCH_AXIS)

    # shard_map (not GSPMD auto-partitioning): the verify kernel's batch
    # inversion is an associative_scan over the batch axis, which GSPMD
    # would implement with cross-device collectives; per-shard it is a
    # pure-local Montgomery product tree, and the ONLY collective left is
    # the explicit psum of the valid-count.
    sm = shard_map(step, mesh=mesh,
                   in_specs=(P(BATCH_AXIS),) * 5,
                   out_specs=(P(BATCH_AXIS), P()))
    return jax.jit(sm, compiler_options=dict(compiler_options) or None)
