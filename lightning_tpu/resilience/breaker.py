"""Per-family circuit breakers for the batched device paths.

Every dispatch family (``verify``, ``route``, ``sign``, ``mesh``) has
an exact host fallback — the bigint verify oracle, host dijkstra, ref
ECDSA sign, the single-device fused program.  A breaker decides WHICH
side runs: after ``threshold`` consecutive device failures it opens and
every dispatch short-circuits to the host path; after an exponential
backoff (with deterministic per-family jitter so herds of breakers
don't probe in lockstep) it half-opens and lets exactly one probe
through — success closes it, failure re-opens with a doubled backoff.

CLN's supervision story is subdaemons that crash and restart
independently; this is the same posture for an accelerator: a flapping
or wedged device degrades ONE family to its host path instead of
wedging the daemon.

State transitions are metered (``clntpu_breaker_*``) and emitted on the
events bus (topic ``breaker_transition``); the `getmetrics` RPC carries
a ``resilience`` section with every breaker's live state.

Knobs::

    LIGHTNING_TPU_BREAKER_THRESHOLD      consecutive failures to trip (5)
    LIGHTNING_TPU_BREAKER_BACKOFF_S      first open→half-open delay (1.0)
    LIGHTNING_TPU_BREAKER_MAX_BACKOFF_S  backoff ceiling (30.0)
    LIGHTNING_TPU_BREAKER_DISABLE=1      breakers never trip (record only)
"""
from __future__ import annotations

import os
import random
import threading
import time

from ..obs import families as _f
from ..utils import events

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_STATE_CODE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}

# cap the exponent so a breaker that flaps for days can't overflow
_MAX_TRIP_EXP = 16


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class CircuitBreaker:
    """Thread-safe three-state breaker (dispatches run on asyncio
    worker threads AND the replay dispatch thread)."""

    def __init__(self, family: str, *, threshold: int | None = None,
                 base_backoff: float | None = None,
                 max_backoff: float | None = None,
                 disabled: bool | None = None,
                 clock=time.monotonic):
        self.family = family
        self.threshold = int(threshold if threshold is not None else
                             _env_float("LIGHTNING_TPU_BREAKER_THRESHOLD", 5))
        self.base_backoff = (base_backoff if base_backoff is not None else
                             _env_float("LIGHTNING_TPU_BREAKER_BACKOFF_S",
                                        1.0))
        self.max_backoff = (max_backoff if max_backoff is not None else
                            _env_float("LIGHTNING_TPU_BREAKER_MAX_BACKOFF_S",
                                       30.0))
        self.disabled = (disabled if disabled is not None else
                         os.environ.get("LIGHTNING_TPU_BREAKER_DISABLE")
                         == "1")
        self._clock = clock
        self._lock = threading.Lock()
        # deterministic per-family jitter stream: reproducible tests,
        # and distinct families still decorrelate their probe times
        self._rng = random.Random(family)
        self.state = CLOSED
        self.consecutive_failures = 0
        self.trips = 0
        self._seq = 0
        self._retry_at = 0.0
        self._open_backoff = 0.0
        _f.BREAKER_STATE.labels(family).set(_STATE_CODE[CLOSED])

    # -- the dispatch-side protocol ---------------------------------------

    def allow(self) -> bool:
        """True → caller may try the device; False → short-circuit to
        the host fallback.  An open breaker whose backoff has elapsed
        half-opens and grants exactly one probe."""
        if self.disabled:
            return True
        evt = None
        try:
            with self._lock:
                if self.state == CLOSED:
                    return True
                if self.state == OPEN and self._clock() >= self._retry_at:
                    evt = self._transition(HALF_OPEN)
                    return True
                # open-and-waiting, or a half-open probe in flight
                _f.BREAKER_SHORT_CIRCUITS.labels(self.family).inc()
                return False
        finally:
            self._emit(evt)

    def record_success(self) -> None:
        evt = None
        with self._lock:
            self.consecutive_failures = 0
            if self.state != CLOSED:
                evt = self._transition(CLOSED)
        self._emit(evt)

    def record_failure(self) -> None:
        _f.BREAKER_FAILURES.labels(self.family).inc()
        evt = None
        with self._lock:
            self.consecutive_failures += 1
            if not self.disabled and (
                    self.state == HALF_OPEN or (
                        self.state == CLOSED
                        and self.consecutive_failures >= self.threshold)):
                evt = self._trip()
        self._emit(evt)

    # -- internals (lock held; transitions return the event payload) ------

    def _trip(self) -> dict:
        self.trips += 1
        backoff = min(self.max_backoff,
                      self.base_backoff
                      * 2.0 ** min(self.trips - 1, _MAX_TRIP_EXP))
        backoff *= 1.0 + 0.1 * self._rng.random()
        self._open_backoff = backoff
        self._retry_at = self._clock() + backoff
        return self._transition(OPEN)

    def _transition(self, to: str) -> dict:
        """State change + metering under the lock; the events-bus
        emission is the CALLER's job once the lock is released — the
        bus runs subscriber callbacks synchronously, and a subscriber
        calling back into snapshot()/allow() (the health engine's
        breaker tap does exactly that shape) would deadlock against a
        non-reentrant Lock.  The PR-9 health-engine class, caught here
        by graftlint's lock-order pass.

        Emitting after release means two threads' events can reach the
        bus out of transition order; ``seq`` (monotonic, assigned under
        the lock) lets a subscriber mirroring state drop the stale one
        instead of latching a wrong terminal state."""
        self.state = to
        self._seq += 1
        _f.BREAKER_STATE.labels(self.family).set(_STATE_CODE[to])
        _f.BREAKER_TRANSITIONS.labels(self.family, to).inc()
        return {
            "family": self.family, "to": to, "seq": self._seq,
            "consecutive_failures": self.consecutive_failures,
            "backoff_s": round(self._open_backoff, 3) if to == OPEN
            else 0.0,
        }

    @staticmethod
    def _emit(evt: dict | None) -> None:
        if evt is not None:
            events.emit("breaker_transition", evt)

    # -- introspection ------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "trips": self.trips,
                "threshold": self.threshold,
            }
            if self.state == OPEN:
                out["retry_in_s"] = round(
                    max(0.0, self._retry_at - self._clock()), 3)
            return out

    def force_open(self) -> None:
        """Test/ops helper: trip immediately regardless of history."""
        with self._lock:
            evt = self._trip()
        self._emit(evt)

    def reset(self) -> None:
        evt = None
        with self._lock:
            self.consecutive_failures = 0
            self.trips = 0
            if self.state != CLOSED:
                evt = self._transition(CLOSED)
        self._emit(evt)


_breakers: dict[str, CircuitBreaker] = {}
_registry_lock = threading.Lock()


def get(family: str) -> CircuitBreaker:
    """Process-wide breaker for a dispatch family (created on first
    use with the env-derived knobs)."""
    brk = _breakers.get(family)
    if brk is None:
        with _registry_lock:
            brk = _breakers.get(family)
            if brk is None:
                brk = _breakers[family] = CircuitBreaker(family)
    return brk


def all_breakers() -> dict[str, CircuitBreaker]:
    return dict(_breakers)


def reset_for_tests() -> None:
    with _registry_lock:
        for brk in _breakers.values():
            brk.reset()
        _breakers.clear()
