"""Dispatch deadlines and supervised-loop restart backoff.

Two failure shapes the breakers can't see: a dispatch that HANGS (a
wedged device runtime, a dead TPU tunnel — the call never returns, so
there is no exception to count) and a flush loop that DIES (an escaped
exception kills the asyncio task; every later submit queues forever).
This module covers both:

* ``guard(aw, family, seam)`` bounds an awaitable by the family's
  configured deadline; a blown deadline is metered
  (``clntpu_deadline_exceeded_total{family,seam}``), emitted on the
  events bus, and surfaces as ``DeadlineExceeded`` — which the caller's
  existing failure handling (breaker + host fallback + future
  resolution) then treats like any other dispatch error.  NOTE: the
  underlying thread (asyncio.to_thread work) cannot be cancelled — the
  guard un-wedges the CALLER; the worker leaks until it returns.

* ``deadline_for(family)`` is the thread-side knob for blocking waits
  (the replay dispatch loop's prepared-bucket queue.get).

* ``RestartBackoff`` paces supervised-loop restarts (GossipIngest /
  RouteService flush loops): exponential from ``base`` to ``cap``,
  reset on a healthy iteration.  Restarts are metered per loop
  (``clntpu_loop_restarts_total{loop}``).

Deadlines default OFF (a cold XLA compile legitimately takes minutes;
a default that kills it would break first-run daemons).  Configure::

    LIGHTNING_TPU_DEADLINE_S            default for every family (0 = off)
    LIGHTNING_TPU_DEADLINE_VERIFY_S     per-family override
    LIGHTNING_TPU_DEADLINE_ROUTE_S
    LIGHTNING_TPU_DEADLINE_MCF_S
    LIGHTNING_TPU_DEADLINE_INGEST_S

(No sign deadline: hsmd's batched sign is a synchronous call on the
caller's thread — nothing could act on a blown deadline there.  Its
hang coverage is the caller's own event-loop supervision.)
"""
from __future__ import annotations

import asyncio
import logging
import os

from ..obs import families as _f
from ..utils import events

log = logging.getLogger("lightning_tpu.resilience.deadline")


class DeadlineExceeded(RuntimeError):
    pass


def deadline_for(family: str) -> float | None:
    """Configured dispatch deadline in seconds, or None (disabled)."""
    raw = os.environ.get(f"LIGHTNING_TPU_DEADLINE_{family.upper()}_S")
    if raw is None:
        raw = os.environ.get("LIGHTNING_TPU_DEADLINE_S")
    if raw is None:
        return None
    try:
        val = float(raw)
    except ValueError:
        return None
    return val if val > 0 else None


def note_exceeded(family: str, seam: str, deadline_s: float) -> None:
    """Meter + emit a blown deadline (thread-side callers that manage
    their own timeout, e.g. the replay dispatch loop's queue.get)."""
    _f.DEADLINE_EXCEEDED.labels(family, seam).inc()
    events.emit("deadline_exceeded", {"family": family, "seam": seam,
                                      "deadline_s": deadline_s})
    log.warning("%s:%s dispatch deadline (%.3fs) exceeded",
                family, seam, deadline_s)


async def guard(aw, family: str, seam: str):
    """Await ``aw`` under the family's deadline (pass-through when none
    is configured)."""
    dl = deadline_for(family)
    if dl is None:
        return await aw
    try:
        return await asyncio.wait_for(aw, dl)
    except asyncio.TimeoutError:
        note_exceeded(family, seam, dl)
        raise DeadlineExceeded(
            f"{family}:{seam} dispatch exceeded {dl:g}s deadline") from None


class RestartBackoff:
    """Exponential restart pacing for a supervised loop."""

    def __init__(self, base: float = 0.05, cap: float = 5.0):
        self.base = base
        self.cap = cap
        self._next = base

    def next(self) -> float:
        delay = self._next
        self._next = min(self.cap, self._next * 2.0)
        return delay

    def reset(self) -> None:
        self._next = self.base


def note_restart(loop: str, error: BaseException, delay: float) -> None:
    """Meter + emit one supervised-loop restart."""
    _f.LOOP_RESTARTS.labels(loop).inc()
    events.emit("loop_restart", {"loop": loop, "error": repr(error),
                                 "restart_delay_s": round(delay, 3)})
    log.exception("%s loop error; restarting in %.2fs", loop, delay,
                  exc_info=error)
