"""Poisoned-batch quarantine: host-side bisection of a failing device
dispatch.

A batched dispatch fails as a UNIT — one malformed row (a shape the
packer mis-flagged, an input that trips a kernel guard, a buffer the
runtime rejects) takes the other few hundred rows of the bucket down
with it.  The reference never has this problem (it verifies serially);
the batched pipelines get the serial behavior back only when they need
it: re-dispatch halves of the failing index set, recursing into
whichever half still raises, until the poison is isolated to single
rows.  Clean subsets complete on the device at most ⌈log2 n⌉ levels
deep (≤ 2·log2 n extra dispatches); the isolated rows are quarantined —
metered per family/reason and handed back to the caller, which
re-checks them on its exact host path (or fails them closed).

``clntpu_quarantine_total{family,reason}`` counts every diverted row;
the events bus carries a ``quarantine`` topic per isolated row.
"""
from __future__ import annotations

import logging

import numpy as np

from ..obs import families as _f
from ..obs import flight as _flight
from ..utils import events

log = logging.getLogger("lightning_tpu.resilience.quarantine")


def note(family: str, reason: str, rows: int = 1) -> None:
    """Meter rows diverted off a device result without a bisect (e.g.
    a readback failure after the dispatch stream already completed)."""
    _f.QUARANTINE.labels(family, reason).inc(rows)
    _flight.note_quarantine(rows)


def bisect(indices, attempt, family: str):
    """Recursively isolate the rows a batched ``attempt`` cannot
    process.

    ``attempt(idx)`` takes an int index array and returns per-index
    results (len == len(idx)), raising if the subset still contains a
    poisoned row.  Returns ``(parts, quarantined)`` where ``parts`` is
    a list of ``(idx, results)`` for every subset that succeeded and
    ``quarantined`` is the list of isolated indices (metered, in
    ascending order).  The caller decides what a quarantined row means
    — the verify path re-checks them on the host oracle, so quarantine
    degrades accuracy never, only throughput.
    """
    parts: list[tuple[np.ndarray, object]] = []
    bad: list[int] = []
    stack = [np.asarray(indices)]
    while stack:
        idx = stack.pop()
        if len(idx) == 0:
            continue
        try:
            parts.append((idx, attempt(idx)))
        except Exception as e:
            if len(idx) == 1:
                row = int(idx[0])
                reason = type(e).__name__
                _f.QUARANTINE.labels(family, reason).inc()
                _flight.note_quarantine(1)
                events.emit("quarantine", {"family": family, "row": row,
                                           "reason": reason})
                log.warning("%s: quarantined row %d (%s: %s)",
                            family, row, reason, e)
                bad.append(row)
            else:
                mid = len(idx) // 2
                stack.append(idx[mid:])
                stack.append(idx[:mid])
    bad.sort()
    return parts, bad
