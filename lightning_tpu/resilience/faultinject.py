"""Deterministic fault injection at the device-path seams.

The reference hardens its channel machinery with dev_disconnect scripts
(`-`/`+` crashes at every protocol message — tests/test_fault_matrix.py
reproduces that matrix); this module is the same idea for the BATCHED
DEVICE paths: named seams inside the dispatch pipelines call
``fire(seam, family)``, and an armed spec makes that call raise or hang
on a deterministic schedule.  The resilience layer (breakers,
quarantine, deadlines) is then exercised end-to-end by re-running the
real workload tests with a representative spec
(tools/run_suite.sh fault-matrix pass).

Spec grammar (comma-separated specs in ``LIGHTNING_TPU_FAULT`` or
``arm()``):

    seam:family:action:rate[:arg]

* ``seam``   — where: ``prep``, ``dispatch``, ``readback``, ``mesh``,
               ``sign``, ``producer``, ``append`` (store append),
               ``commit`` (db commit) (or ``*``).
* ``family`` — which dispatch family: ``verify``, ``route``, ``sign``,
               ``mesh``, ``ingest``, ``store``, ``db`` (or ``*``).
* ``action`` — ``raise`` (throw ``FaultInjected``), ``hang``
               (sleep ``arg`` seconds, default 0.05, then continue),
               or ``crash`` (freeze a crash incident bundle, flush
               output, then ``os._exit(arg)`` — default exit code 137,
               the kill -9 convention; tools/crashmatrix.py drives
               every seam through this and asserts the restart
               recovers).
* ``rate``   — fraction of matching calls that fire, in (0, 1];
               default 1.  Firing is DETERMINISTIC, not random: spec
               call counts walk a Bresenham schedule
               (fire iff ⌊n·rate⌋ > ⌊(n−1)·rate⌋), so a given spec
               fires on the same calls in every run.

Examples::

    LIGHTNING_TPU_FAULT=dispatch:verify:raise:0.1
    LIGHTNING_TPU_FAULT=sign:sign:raise:0.5,mesh:mesh:raise:1
    LIGHTNING_TPU_FAULT=producer:verify:hang:1:30     # 30 s hang, every call
    LIGHTNING_TPU_FAULT=append:store:crash:1          # die mid-append

Disarmed (no env, nothing ``arm()``-ed), ``fire()`` is one dict lookup
— cheap enough for per-bucket dispatch sites.
"""
from __future__ import annotations

import contextlib
import logging
import math
import os
import threading
import time
from dataclasses import dataclass, field

from ..obs import families as _f
from ..obs import flight as _flight
from ..utils import events

log = logging.getLogger("lightning_tpu.resilience.faultinject")

SEAMS = ("prep", "dispatch", "readback", "mesh", "sign", "producer",
         "append", "commit")
ACTIONS = ("raise", "hang", "crash")


class FaultInjected(RuntimeError):
    """The injected failure: deliberately a RuntimeError subclass so it
    walks the exact handler paths a real XlaRuntimeError would."""


@dataclass
class _Spec:
    seam: str
    family: str
    action: str
    rate: float
    arg: float
    raw: str
    calls: int = 0
    fired: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    def should_fire(self) -> bool:
        with self._lock:
            self.calls += 1
            n = self.calls
            hit = math.floor(n * self.rate) > math.floor((n - 1) * self.rate)
            if hit:
                self.fired += 1
            return hit


def parse(spec_str: str) -> list[_Spec]:
    """Parse a spec string; raises ValueError on bad grammar."""
    out = []
    for part in spec_str.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 3 or len(fields) > 5:
            raise ValueError(
                f"fault spec {part!r}: want seam:family:action:rate[:arg]")
        seam, family, action = fields[0], fields[1], fields[2]
        if action not in ACTIONS:
            raise ValueError(
                f"fault spec {part!r}: action must be one of {ACTIONS}")
        rate = float(fields[3]) if len(fields) > 3 and fields[3] else 1.0
        if not 0.0 < rate <= 1.0:
            raise ValueError(f"fault spec {part!r}: rate must be in (0, 1]")
        # arg: hang = sleep seconds; crash = exit code (137 mirrors the
        # shell's kill -9 convention, so harnesses can tell an injected
        # kill from an ordinary nonzero exit)
        default_arg = 137.0 if action == "crash" else 0.05
        arg = float(fields[4]) if len(fields) > 4 else default_arg
        out.append(_Spec(seam, family, action, rate, arg, part))
    return out


# programmatically armed specs (tests: the arm() context manager)
_armed: list[_Spec] = []
# env specs, cached against the env string so monkeypatch.setenv works
# and per-spec Bresenham counters survive across fire() calls
_env_cache: tuple[str | None, list[_Spec]] = (None, [])
_env_lock = threading.Lock()


def _env_specs() -> list[_Spec]:
    global _env_cache
    env = os.environ.get("LIGHTNING_TPU_FAULT", "")
    cached_env, specs = _env_cache
    if env == cached_env:
        return specs
    # parse (and warn) OUTSIDE the lock: parse is pure, so a racing
    # second parse of the same env is benign — but log handlers are
    # pluggable and may block or re-enter (graftlint lock-order)
    try:
        specs = parse(env)
    except ValueError as e:
        log.warning("ignoring bad LIGHTNING_TPU_FAULT: %s", e)
        specs = []
    with _env_lock:
        _env_cache = (env, specs)
    return specs


def fire(seam: str, family: str) -> None:
    """Injection point: no-op unless an armed spec matches this seam
    and family AND its deterministic schedule says fire."""
    if not _armed and not os.environ.get("LIGHTNING_TPU_FAULT"):
        return
    for spec in (*_env_specs(), *_armed):
        if spec.seam not in ("*", seam) or spec.family not in ("*", family):
            continue
        if not spec.should_fire():
            continue
        _f.FAULT_INJECTED.labels(seam, family, spec.action).inc()
        # stamp the in-flight DispatchRecord (if any) so the flight
        # ring shows WHICH dispatch ate this injection (doc/tracing.md)
        _flight.note_fault(seam, family)
        events.emit("fault_injected",
                    {"seam": seam, "family": family, "spec": spec.raw})
        if spec.action == "hang":
            time.sleep(spec.arg)
        elif spec.action == "crash":
            _crash(seam, family, spec)
        else:
            raise FaultInjected(
                f"injected fault at {seam}:{family} (spec {spec.raw!r})")


def crash_armed(seam: str, family: str) -> bool:
    """True when a crash-action spec matches this seam+family.  Does NOT
    consume any spec's Bresenham schedule — seams that must stage a
    partial write for the kill to land mid-record (the store append
    torn-tail window) check this before deciding where to place their
    ``fire()`` call."""
    if not _armed and not os.environ.get("LIGHTNING_TPU_FAULT"):
        return False
    return any(
        s.action == "crash"
        and s.seam in ("*", seam) and s.family in ("*", family)
        for s in (*_env_specs(), *_armed))


def _crash(seam: str, family: str, spec: _Spec) -> None:
    """The crash action: freeze a crash bundle, flush, ``os._exit``.

    ``os._exit`` skips atexit/excepthook on purpose — the whole point is
    to model a SIGKILL-grade death that gives NOTHING a chance to clean
    up — so the incident bundle the black box owes the next boot
    (doc/recovery.md: "prior crash bundle discovered") must be captured
    synchronously here, before the exit."""
    log.critical("injected crash at %s:%s (spec %r): freezing incident "
                 "bundle, then os._exit", seam, family, spec.raw)
    try:
        from ..obs import incident as _incident

        rec = _incident.current()
        if rec is not None and rec.running:
            rec.note_crash(
                f"injected crash at {seam}:{family}",
                {"seam": seam, "family": family, "spec": spec.raw})
    except Exception:
        log.exception("crash-bundle capture failed; exiting anyway")
    try:
        import sys as _sys

        _sys.stdout.flush()
        _sys.stderr.flush()
        for h in logging.getLogger().handlers:
            h.flush()
    except Exception:
        pass
    os._exit(int(spec.arg))


@contextlib.contextmanager
def arm(spec_str: str):
    """Programmatic arming for tests: faults active inside the with
    block (composes with any env specs)."""
    specs = parse(spec_str)
    _armed.extend(specs)
    try:
        yield specs
    finally:
        for s in specs:
            _armed.remove(s)


def active_specs() -> list[str]:
    return [s.raw for s in (*_env_specs(), *_armed)]


def reset_for_tests() -> None:
    global _env_cache
    _armed.clear()
    _env_cache = (None, [])
