"""Device-path resilience: supervision around the batched dispatch
families (doc/resilience.md).

Every batched device path in this repo — the store-replay verify
pipeline (gossip/verify.py), the GossipIngest and RouteService flush
loops, the hsmd batched sign, the mesh-sharded EC stage — treats the
accelerator as a peer that can fail, hang, or poison a batch.  This
package is the common machinery:

* ``breaker``      — per-family circuit breakers (closed → open →
                     half-open probe with exponential backoff + jitter)
                     gating device dispatch vs. the host fallback;
* ``deadline``     — configurable dispatch deadlines + loop restart
                     backoff, so a hung worker surfaces as a metered
                     event instead of a silent stall;
* ``quarantine``   — host-side bisection of a poisoned batch: isolate
                     the offending rows, complete the remainder;
* ``overload``     — watermarked backlog control shared by the flush
                     queues: degradation ladder, priority-aware load
                     shedding, adaptive flush widening, transport
                     backpressure, TRY_AGAIN admission control
                     (doc/overload.md);
* ``faultinject``  — deterministic fault injectors at named seams
                     (``LIGHTNING_TPU_FAULT=dispatch:verify:raise:0.1``)
                     driving the scripted fault matrix in
                     tools/run_suite.sh.

Deliberately jax-free: hot-path modules import this at module scope and
exposition-only consumers (tools/obs_snapshot.py) can reach the metric
families without paying the crypto-stack import.
"""
from __future__ import annotations

from . import (breaker, deadline, faultinject,  # noqa: F401
               overload, quarantine)

# the canonical dispatch families every daemon has (a breaker exists
# for each even before its first dispatch, so getmetrics' resilience
# section and a fresh scrape agree on the vocabulary)
FAMILIES = ("verify", "route", "sign", "mesh")


def resilience_snapshot() -> dict:
    """The `resilience` section of the getmetrics RPC result: breaker
    states plus whatever fault specs are currently armed."""
    return {
        "breakers": {f: breaker.get(f).snapshot() for f in FAMILIES},
        "faults_armed": faultinject.active_specs(),
    }


def reset_for_tests() -> None:
    breaker.reset_for_tests()
    faultinject.reset_for_tests()
    overload.reset_for_tests()
