"""Unified overload control for the batched-dispatch consumers.

Every queue in front of a batched device dispatch — GossipIngest's
signature queue, RouteService's query queue — used to be unbounded: a
sustained gossip storm or an RPC flood grew memory without limit and
destroyed tail latency instead of degrading gracefully.  This module is
the shared answer (doc/overload.md):

* **Watermarks + degradation ladder.**  Each consumer registers an
  ``OverloadController`` with a low and a high watermark over its
  backlog (queued + in-flight work units).  The ladder state — NORMAL
  below the low watermark, ELEVATED between the two, SATURATED at or
  above the high one — is a first-class observable:
  ``clntpu_overload_state{family}``, transition counters, an
  ``overload_state`` events topic, and a ``getmetrics`` overload
  section.

* **Adaptive flush window.**  As pressure rises the controller widens
  the consumer's flush trigger (size threshold and latency budget)
  from its base toward ``base * LIGHTNING_TPU_FLUSH_WIDEN``: batches
  grow exactly when dispatch overhead matters most, amortizing the
  fixed per-dispatch cost against the storm.

* **Priority-aware load shedding.**  At the high watermark, admission
  becomes priority-ordered: own-node/own-channel updates (PRIO_OWN)
  outrank fresh third-party channel updates and announcements
  (PRIO_FRESH), which outrank node announcements and other redundant
  traffic (PRIO_BULK).  Lower priorities shed first; each higher class
  keeps one more ``high_wm // 4`` band of headroom, so the queue is
  hard-bounded at ``high_wm + 2 * (high_wm // 4)``.  Every shed is
  metered (``clntpu_shed_total{family,priority,reason}``) AND recorded
  in a bounded shed ring carrying the message identity — shed traffic
  is re-requestable (a peer can be re-queried for the scids), never
  silently dropped.

* **Backpressure propagation.**  ``wait_capacity()`` gives transports a
  bounded, fair pause point: while the family is SATURATED a caller
  (the per-peer gossip read path) waits — at most
  ``LIGHTNING_TPU_BACKPRESSURE_MAX_S`` per message, every waiter woken
  together when the backlog drains below the low watermark — so socket
  reads stop and TCP pushes back on the remote instead of us buffering
  its storm.

* **Admission control.**  ``Overloaded`` is the retryable rejection:
  RouteService raises it once its queue crosses the high watermark and
  the JSON-RPC layer maps it to a ``TRY_AGAIN`` error carrying a
  ``retry_after_s`` hint derived from the observed drain rate (doubled
  while the family's circuit breaker is open — the host fallback
  drains slower).

Deliberately jax-free (like the rest of ``resilience``): hot paths
import it at module scope and exposition-only consumers reach the
snapshot without the crypto stack.

Determinism contract: admission compares the backlog snapshot (queued
+ in-flight) against per-priority limits — a pure function of observed
state at submit time.  A scripted storm that submits without yielding
to the event loop keeps in-flight at zero, so its shed set is a pure
function of the storm content: the property
tests/test_zz_overload.py pins bare and under the fault matrix.  Live
sheds additionally depend on flush timing, but every shed is metered
AND ring-recorded, and the replay-parity invariant (the accepted set
equals an unthrottled replay of the non-shed subset) is
timing-independent — tools/loadgen.py asserts it on every soak.
"""
from __future__ import annotations

import asyncio
import collections
import os
import time

from ..obs import families as _f
from ..utils import events

# -- knobs (doc/overload.md; registry-sync keeps doc/knobs.md honest) ------
# max widening factor for flush size/window under full pressure
FLUSH_WIDEN = int(os.environ.get("LIGHTNING_TPU_FLUSH_WIDEN", "8"))
# bounded per-message transport pause while saturated
BACKPRESSURE_MAX_S = float(
    os.environ.get("LIGHTNING_TPU_BACKPRESSURE_MAX_S", "0.25"))
# shed ring capacity (loadgen/selfcheck raise this to capture every shed)
SHED_RING = int(os.environ.get("LIGHTNING_TPU_SHED_RING", "1024"))

# -- ladder states ---------------------------------------------------------
NORMAL, ELEVATED, SATURATED = 0, 1, 2
STATE_NAMES = ("normal", "elevated", "saturated")

# -- priorities (lower value = more important, sheds last) -----------------
PRIO_OWN, PRIO_FRESH, PRIO_BULK, PRIO_QUERY = 0, 1, 2, 3
PRIO_NAMES = ("own", "fresh", "bulk", "query")

_M_SHED = _f.SHED
_M_STATE = _f.OVERLOAD_STATE
_M_TRANSITIONS = _f.OVERLOAD_TRANSITIONS
_M_BP_WAITS = _f.BACKPRESSURE_WAITS
_M_BP_SECONDS = _f.BACKPRESSURE_WAIT_SECONDS


class Overloaded(RuntimeError):
    """Retryable admission rejection: the consumer's backlog is past its
    high watermark.  The JSON-RPC layer maps this to TRY_AGAIN with the
    ``retry_after_s`` hint in the error data."""

    def __init__(self, family: str, retry_after_s: float, backlog: int):
        super().__init__(
            f"{family} overloaded (backlog {backlog}); "
            f"retry in {retry_after_s:.2f}s")
        self.family = family
        self.retry_after_s = retry_after_s
        self.backlog = backlog


class _ShedRecord(dict):
    """One shed message (a plain dict; class only for isinstance tests)."""


class OverloadController:
    """Watermarked backlog supervision for one dispatch family."""

    def __init__(self, family: str, high_wm: int, low_wm: int = 0, *,
                 breaker_family: str | None = None,
                 now=time.monotonic):
        if high_wm <= 0:
            raise ValueError("high watermark must be positive")
        self.family = family
        self.high_wm = int(high_wm)
        self.low_wm = int(low_wm) or max(1, self.high_wm // 2)
        if self.low_wm > self.high_wm:
            raise ValueError("low watermark above high watermark")
        # the breaker whose open state slows this family's drain (the
        # ladder is wired into the breaker machinery through the
        # retry-after hint and the snapshot)
        self.breaker_family = breaker_family or family
        self.now = now
        self._headroom = max(1, self.high_wm // 4)
        self.hard_cap = self.high_wm + 2 * self._headroom
        self.pending = 0         # queued units (admission input)
        self.inflight = 0        # units inside a running flush
        self.peak_backlog = 0
        self.state = NORMAL
        self.shed_counts: dict[tuple[str, str], int] = {}
        # drain-rate EWMA (units/second) feeding the retry-after hint
        self._drain_rate = 0.0
        self._drained = asyncio.Event()
        self._drained.set()
        _M_STATE.labels(family).set(NORMAL)

    # -- backlog + ladder --------------------------------------------------

    def update(self, pending: int, inflight: int = 0) -> None:
        """Report the consumer's current queue occupancy.  Transitions
        the ladder, wakes backpressure waiters on drain."""
        self.pending = pending
        self.inflight = inflight
        total = pending + inflight
        if total > self.peak_backlog:
            self.peak_backlog = total
        if total >= self.high_wm:
            state = SATURATED
        elif total >= self.low_wm:
            # hysteresis: once saturated, stay saturated until the
            # backlog falls below the LOW watermark (no flapping)
            state = SATURATED if self.state == SATURATED else ELEVATED
        else:
            state = NORMAL
        if state != self.state:
            self.state = state
            _M_STATE.labels(self.family).set(state)
            _M_TRANSITIONS.labels(self.family, STATE_NAMES[state]).inc()
            events.emit("overload_state",
                        {"family": self.family,
                         "state": STATE_NAMES[state],
                         "backlog": total})
        if total < self.low_wm:
            self._drained.set()
        elif state == SATURATED:
            self._drained.clear()

    # -- admission / shedding ---------------------------------------------

    def _limit(self, priority: int) -> int:
        """Queue depth past which `priority` sheds: each class above
        BULK keeps one more headroom band; nothing queues past the
        hard cap."""
        if priority <= PRIO_OWN:
            return self.hard_cap
        if priority == PRIO_FRESH:
            return self.high_wm + self._headroom
        return self.high_wm

    def admit(self, priority: int, n: int = 1) -> bool:
        """Admission against the full backlog snapshot (queued +
        in-flight): work inside a running flush still occupies memory
        and drain capacity, so it counts — the queue cannot quietly
        refill to the watermark while a long flush is out.  ``n`` is
        the candidate's unit weight (a channel_announcement is 4
        signatures): the post-admission backlog must stay within the
        limit, so the hard cap is a true bound, not cap + weight - 1.
        See the module docstring's determinism contract."""
        return self.pending + self.inflight + n <= self._limit(priority)

    def shed(self, priority: int, reason: str, **key) -> None:
        """Meter + flight-record one shed message.  ``key`` carries the
        message identity (kind/scid/node_id/timestamp...) so shed
        traffic is re-requestable and a replay harness can reconstruct
        the non-shed subset exactly."""
        pname = PRIO_NAMES[priority]
        _M_SHED.labels(self.family, pname, reason).inc()
        k = (pname, reason)
        self.shed_counts[k] = self.shed_counts.get(k, 0) + 1
        rec = _ShedRecord(family=self.family, priority=pname,
                          reason=reason)
        rec.update(key)
        _shed_ring.append(rec)

    # -- adaptive flush widening ------------------------------------------

    def _pressure(self) -> float:
        """0.0 at/below the low watermark, 1.0 at/above the high one."""
        total = self.pending + self.inflight
        if total <= self.low_wm:
            return 0.0
        span = max(1, self.high_wm - self.low_wm)
        return min(1.0, (total - self.low_wm) / span)

    def widen_factor(self) -> float:
        """1.0 when calm, up to FLUSH_WIDEN under full pressure."""
        return 1.0 + self._pressure() * (max(1, FLUSH_WIDEN) - 1)

    def flush_target(self, base: int) -> int:
        """The consumer's adaptive size trigger: batches widen from
        ``base`` toward ``base * FLUSH_WIDEN`` as pressure rises,
        amortizing per-dispatch overhead exactly when it matters."""
        return max(1, int(base * self.widen_factor()))

    def window_s(self, base_ms: float) -> float:
        """The adaptive latency budget (seconds) for the flush window —
        stretched under pressure for the same reason as flush_target."""
        return base_ms * self.widen_factor() / 1000.0

    # -- backpressure ------------------------------------------------------

    async def wait_capacity(self, max_wait: float | None = None) -> float:
        """Pause the caller while this family is SATURATED: a bounded,
        fair transport-side backpressure point.  Returns the seconds
        actually waited.  Every waiter is released together when the
        backlog drains below the low watermark; the per-call bound
        (default LIGHTNING_TPU_BACKPRESSURE_MAX_S) keeps a saturated
        steady state from starving any peer forever."""
        if self.state != SATURATED:
            return 0.0
        bound = BACKPRESSURE_MAX_S if max_wait is None else max_wait
        _M_BP_WAITS.labels(self.family).inc()
        t0 = self.now()
        try:
            await asyncio.wait_for(self._drained.wait(), bound)
        except asyncio.TimeoutError:
            pass
        waited = max(0.0, self.now() - t0)
        _M_BP_SECONDS.labels(self.family).observe(waited)
        return waited

    # -- drain-rate / retry hint ------------------------------------------

    def note_drain(self, units: int, seconds: float) -> None:
        """Feed one completed flush into the drain-rate EWMA."""
        if units <= 0 or seconds <= 0:
            return
        rate = units / seconds
        self._drain_rate = (rate if self._drain_rate == 0.0
                            else 0.7 * self._drain_rate + 0.3 * rate)

    def retry_after_s(self) -> float:
        """How long a rejected caller should wait before retrying:
        backlog over the observed drain rate, clamped to [0.05, 5]s,
        doubled while this family's circuit breaker is open (the host
        fallback drains slower than the device path)."""
        total = self.pending + self.inflight
        if self._drain_rate > 0:
            hint = total / self._drain_rate
        else:
            hint = 0.1
        hint = min(5.0, max(0.05, hint))
        from . import breaker as _breaker

        if _breaker.get(self.breaker_family).state == "open":
            hint = min(10.0, hint * 2)
        return hint

    def overloaded(self) -> Overloaded:
        """The admission rejection for this family, hint included."""
        return Overloaded(self.family, self.retry_after_s(),
                          self.pending + self.inflight)

    # -- exposition --------------------------------------------------------

    def snapshot(self) -> dict:
        from . import breaker as _breaker

        return {
            "state": STATE_NAMES[self.state],
            "backlog": self.pending + self.inflight,
            "pending": self.pending,
            "inflight": self.inflight,
            "peak_backlog": self.peak_backlog,
            "low_wm": self.low_wm,
            "high_wm": self.high_wm,
            "hard_cap": self.hard_cap,
            "widen_factor": round(self.widen_factor(), 3),
            "drain_rate_per_s": round(self._drain_rate, 3),
            "retry_after_s": round(self.retry_after_s(), 3),
            "breaker": _breaker.get(self.breaker_family).state,
            "shed": {f"{p}:{r}": n
                     for (p, r), n in sorted(self.shed_counts.items())},
        }


# -- module registry -------------------------------------------------------

_controllers: dict[str, OverloadController] = {}
_shed_ring: collections.deque = collections.deque(maxlen=SHED_RING)


def controller(family: str, high_wm: int, low_wm: int = 0, *,
               breaker_family: str | None = None,
               now=time.monotonic) -> OverloadController:
    """Create + register the controller for `family` (the registry
    feeds the getmetrics overload section; last construction wins,
    which is what tests constructing many consumers want)."""
    ctl = OverloadController(family, high_wm, low_wm,
                             breaker_family=breaker_family, now=now)
    _controllers[family] = ctl
    return ctl


def get(family: str) -> OverloadController | None:
    return _controllers.get(family)


def recent_sheds(limit: int | None = None) -> list[dict]:
    """The shed flight ring, oldest first (bounded by
    LIGHTNING_TPU_SHED_RING) — the re-request source of truth."""
    out = [dict(r) for r in _shed_ring]
    if limit is not None:
        out = out[-limit:]
    return out


def snapshot() -> dict:
    """The `overload` section of getmetrics (doc/overload.md)."""
    return {
        "families": {f: c.snapshot()
                     for f, c in sorted(_controllers.items())},
        "sheds_recorded": len(_shed_ring),
        "recent_sheds": recent_sheds(64),
    }


def reset_for_tests() -> None:
    _controllers.clear()
    _shed_ring.clear()
