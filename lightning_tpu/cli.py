"""lightning-cli equivalent: one-shot JSON-RPC over the unix socket.

Usage:
  python -m lightning_tpu.cli --rpc-file /path/lightning-rpc getinfo
  python -m lightning_tpu.cli ... getroute id=<hex> amount_msat=1000
"""
from __future__ import annotations

import argparse
import json
import socket
import sys


def call(rpc_path: str, method: str, params: dict, timeout: float = 60.0):
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(timeout)
    s.connect(rpc_path)
    req = {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
    s.sendall(json.dumps(req).encode())
    buf = b""
    decoder = json.JSONDecoder()
    while True:
        chunk = s.recv(65536)
        if not chunk:
            raise ConnectionError("rpc socket closed without a response")
        buf += chunk
        try:
            resp, _ = decoder.raw_decode(buf.decode("utf8").lstrip())
            s.close()
            return resp
        except json.JSONDecodeError:
            continue


def _coerce(v: str):
    if v and (v[0] in "{[" or v in ("true", "false", "null")):
        return json.loads(v)
    # only short all-digit strings become ints: a 66-char hex pubkey that
    # happens to be all digits must stay a string
    if v.isdigit() and len(v) <= 18:
        return int(v)
    return v


def main() -> int:
    p = argparse.ArgumentParser(prog="lightning_tpu.cli")
    p.add_argument("--rpc-file", required=True)
    p.add_argument("method")
    p.add_argument("params", nargs="*", metavar="key=value")
    args = p.parse_args()
    params = {}
    for kv in args.params:
        if "=" not in kv:
            print(f"bad param {kv!r}: want key=value", file=sys.stderr)
            return 2
        k, v = kv.split("=", 1)
        params[k] = _coerce(v)
    resp = call(args.rpc_file, args.method, params)
    if "error" in resp:
        print(json.dumps(resp["error"], indent=1), file=sys.stderr)
        return 1
    print(json.dumps(resp["result"], indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
